"""TPU-window watcher: catch a live axon-tunnel window and bank it.

The tunnel to the real chip has hung through entire rounds (VERDICT r2-r4:
every driver bench attempt `[killed]` at its hard timeout), while
interactive windows do open occasionally (round 3 measured 1384 img/s in
one). This daemon makes sure no window is ever missed again:

  loop:
    probe the TPU in a CHILD process with a hard wall-clock kill
    (the tunnel HANGS rather than erroring — memory/axon-tpu-tunnel-
    flakiness — so an in-process timeout can never fire);
    if dead  -> sleep and re-probe;
    if alive -> run the measurement playbook, cheapest-first, each step
                its own hard-timeout child:
                  1. bench.py ladder (banks resnet b64->256->1024 + remat
                     and bert seq128 -> seq384 -> flash into
                     BENCH_BANK.json with git_sha + timestamp)
                  2. bench_bert.py seq-384 flash probe
                  3. hlo_scan cost census (PERF.md MFU inputs)
                commit the bank + MEASURED_r05/ after every step that
                changed something — a window can die mid-playbook and we
                keep what was banked.

Exits 0 once every goal is banked (so a supervising session is notified),
or at the lifetime deadline. Run:  python tools/tpu_watcher.py &
"""

import json
import os
import signal
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, os.environ.get("WATCH_OUT", "MEASURED_r05"))
LOG = os.path.join(OUT, "watcher.log")
PROBE_INTERVAL = float(os.environ.get("WATCH_PROBE_INTERVAL", "300"))
PROBE_TIMEOUT = float(os.environ.get("WATCH_PROBE_TIMEOUT", "120"))
LIFETIME_H = float(os.environ.get("WATCH_HOURS", "11"))

PROBE_SRC = r"""
import jax, jax.numpy as jnp
devs = [d for d in jax.devices() if d.platform != "cpu"]
assert devs, "no accelerator device"
x = jax.device_put(jnp.ones((256, 256), jnp.bfloat16), devs[0])
jax.jit(lambda a: (a @ a).sum())(x).block_until_ready()
print("PROBE_OK", devs[0].platform, flush=True)
"""


def log(msg):
    line = "%s %s" % (time.strftime("%H:%M:%S", time.gmtime()), msg)
    print(line, flush=True)
    try:
        with open(LOG, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


def run_killable(cmd, timeout, env=None, log_name=None):
    """Run cmd in its own process group; SIGKILL the whole group on
    timeout (a hung tunnel call cannot be interrupted any other way).
    Returns (rc, tail_of_output)."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    logf = open(os.path.join(OUT, log_name), "ab") if log_name else None
    try:
        proc = subprocess.Popen(
            cmd,
            stdout=logf or subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
            cwd=ROOT,
            env=full_env,
            start_new_session=True,
        )
        try:
            proc.wait(timeout=timeout)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            rc = -9
    finally:
        if logf:
            logf.close()
    tail = ""
    if log_name:
        try:
            with open(os.path.join(OUT, log_name), "rb") as f:
                f.seek(max(0, os.path.getsize(os.path.join(OUT, log_name)) - 2000))
                tail = f.read().decode("utf-8", "replace")
        except OSError:
            pass
    return rc, tail


def probe():
    rc, _ = run_killable(
        [sys.executable, "-c", PROBE_SRC], PROBE_TIMEOUT, log_name="probe.log"
    )
    return rc == 0


import bench  # the bank module (repo root); honors BENCH_BANK_PATH

HLO_GOALS = ("hlo_resnet", "hlo_bert", "hlo_bert_flash")


def goals_state():
    bank = bench.load_bank()
    return {
        "resnet": any(k.startswith("resnet50") for k in bank),
        "resnet_big": any(
            k.startswith("resnet50") and bank[k].get("batch", 0) >= 256 for k in bank
        ),
        "bert384": "bert_seq384" in bank,
        "bert384_flash": "bert_seq384_flash" in bank,
        "gpt": "gpt_seq1024" in bank,
        "hlo": all(
            os.path.exists(os.path.join(OUT, n + ".json")) for n in HLO_GOALS
        ),
    }


def commit_if_changed(msg):
    """Commit the bank + measured dir; retry briefly on index.lock races
    with a concurrently working session."""
    paths = [os.path.relpath(OUT, ROOT)]
    bank_rel = os.path.relpath(bench.BANK_PATH, ROOT)
    if not bank_rel.startswith(".."):  # only committable when inside the repo
        paths.insert(0, bank_rel)
    existing = [p for p in paths if os.path.exists(os.path.join(ROOT, p))]
    if not existing:
        return
    for attempt in range(5):
        st = subprocess.run(
            ["git", "status", "--porcelain", "--"] + existing,
            capture_output=True, text=True, cwd=ROOT,
        )
        if not st.stdout.strip():
            return  # nothing new
        add = subprocess.run(["git", "add", "--"] + existing, cwd=ROOT,
                             capture_output=True, text=True)
        com = subprocess.run(
            ["git", "commit", "-m", msg, "--"] + existing,
            cwd=ROOT, capture_output=True, text=True,
        )
        if com.returncode == 0:
            log("committed: %s" % msg)
            return
        if "index.lock" in (add.stderr + com.stderr + com.stdout):
            time.sleep(3 + attempt * 3)
            continue
        log("commit failed: %s" % (com.stderr or com.stdout)[:200])
        return


def playbook(deadline):
    """One live-window measurement pass; returns True if all goals met.
    Every step's timeout is capped at the lifetime deadline, and steps
    whose goals are already banked are skipped (a short window must go
    straight to whatever is still missing)."""
    g0 = goals_state()
    log("window open; goals before: %s" % g0)

    def slot(want):
        return min(want, max(0.0, deadline - time.time()))

    # 1. the full bench ladder — banks everything it measures; skipped
    #    once every DENSE bench goal is in the bank so a later window can
    #    spend itself on the still-missing steps (the flash rung has its
    #    own dedicated step 2 — rerunning the 13-minute ladder just to
    #    reach the final flash rung would waste a short window)
    bench_goals = ("resnet", "resnet_big", "bert384")
    if not all(g0[k] for k in bench_goals) and slot(1550) > 120:
        budget = slot(1550)
        rc, tail = run_killable(
            [sys.executable, "bench.py"],
            budget,
            env={"BENCH_TIMEOUT": str(int(budget - 50))},
            log_name="bench_ladder.log",
        )
        log("bench ladder rc=%s" % rc)
        commit_if_changed("bank TPU measurements from live window (bench ladder)")

    # 2. flash probe at seq 384 if the ladder didn't get to it
    if (goals_state()["bert384"] and not goals_state()["bert384_flash"]
            and slot(600) > 120):
        budget = slot(600)
        rc, _ = run_killable(
            [sys.executable, "bench_bert.py"],
            budget,
            env={"BENCH_BERT_SEQ": "384", "BENCH_FLASH": "1",
                 "BENCH_BUDGET_S": str(int(budget - 50))},
            log_name="bench_bert_flash.log",
        )
        log("bert flash probe rc=%s" % rc)
        commit_if_changed("bank TPU flash-attention measurement from live window")

    # 2b. GPT-2-small causal-LM rung (third model family; exercises the
    #     causal flash path). Dense first — banks gpt_seq1024 — then a
    #     best-effort flash variant if the window still has room.
    if not goals_state()["gpt"] and slot(700) > 120:
        budget = slot(700)
        rc, _ = run_killable(
            [sys.executable, "bench_gpt.py"],
            budget,
            # BENCH_FLASH/BENCH_GPT_SEQ pinned: ambient values (say, from
            # a manual probe's shell) would bank a different slot and
            # leave the dense gpt_seq1024 goal permanently unmet
            env={"BENCH_FLASH": "0", "BENCH_GPT_SEQ": "1024",
                 "BENCH_BUDGET_S": str(int(budget - 50))},
            log_name="bench_gpt.log",
        )
        log("gpt bench rc=%s" % rc)
        commit_if_changed("bank TPU GPT-2 LM measurement from live window")
    if (goals_state()["gpt"]
            and "gpt_seq1024_flash" not in bench.load_bank()
            and slot(600) > 120):
        budget = slot(600)
        rc, _ = run_killable(
            [sys.executable, "bench_gpt.py"],
            budget,
            env={"BENCH_FLASH": "1", "BENCH_GPT_SEQ": "1024",
                 "BENCH_BUDGET_S": str(int(budget - 50))},
            log_name="bench_gpt_flash.log",
        )
        log("gpt flash probe rc=%s" % rc)
        commit_if_changed("bank TPU GPT-2 causal-flash measurement from live window")

    # 3. HLO cost census for the PERF.md MFU numbers
    hlo_args = {
        "hlo_resnet": ["--model", "resnet", "--batch", "256"],
        "hlo_bert": ["--model", "bert", "--batch", "24", "--seq", "384"],
        "hlo_bert_flash":
            ["--model", "bert", "--batch", "24", "--seq", "384", "--flash", "1"],
    }
    for name in HLO_GOALS:
        args = hlo_args[name]
        dst = os.path.join(OUT, name + ".json")
        if os.path.exists(dst) or slot(700) < 120:
            continue
        rc, _ = run_killable(
            [sys.executable, "tools/hlo_scan.py"] + args + ["--out", dst],
            slot(700),
            log_name="hlo_scan.log",
        )
        log("hlo_scan %s rc=%s" % (name, rc))
    commit_if_changed("record TPU HLO cost census from live window")

    # 4. long-context bonus (lowest priority — only leftover window time):
    #    GPT seq-4096 through the causal flash kernel. Requires the seq-1024
    #    flash rung banked first: it proves the kernel's TPU lowering before
    #    spending a window on the 16x-larger attention problem.
    if ("gpt_seq1024_flash" in bench.load_bank()
            and "gpt_seq4096_flash" not in bench.load_bank()
            and slot(700) > 120):
        budget = slot(700)
        rc, _ = run_killable(
            [sys.executable, "bench_gpt.py"],
            budget,
            env={"BENCH_GPT_SEQ": "4096", "BENCH_FLASH": "1",
                 "BENCH_BUDGET_S": str(int(budget - 50))},
            log_name="bench_gpt_longctx.log",
        )
        log("gpt long-context probe rc=%s" % rc)
        commit_if_changed(
            "bank TPU long-context GPT measurement from live window")

    g1 = goals_state()
    log("goals after playbook: %s" % g1)
    return all(g1.values())


def main():
    os.makedirs(OUT, exist_ok=True)
    deadline = time.time() + LIFETIME_H * 3600
    log("watcher start; lifetime %.1fh, probe every %.0fs" % (LIFETIME_H, PROBE_INTERVAL))
    if all(goals_state().values()):
        log("all goals already banked; exiting")
        return 0
    n = 0
    while time.time() < deadline:
        n += 1
        if probe():
            log("probe #%d: TPU ALIVE" % n)
            if playbook(deadline):
                log("all goals banked; watcher done")
                return 0
            # partial window — re-probe soon in case it is still open
            time.sleep(60)
        else:
            if n % 6 == 1:
                log("probe #%d: tunnel dead (and %d more silent probes)" % (n, 5))
            time.sleep(max(0.0, min(PROBE_INTERVAL, deadline - time.time())))
    log("lifetime deadline reached; exiting with goals: %s" % goals_state())
    return 1


if __name__ == "__main__":
    sys.exit(main())
