"""Headline benchmark: ResNet-50 training throughput (images/sec) on one
chip (BASELINE.md metric 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the reference's V100+NCCL path. The
reference publishes no numbers in-repo (BASELINE.md), so the baseline
constant below is the commonly reported PaddlePaddle-era ResNet-50 fp32
V100 figure (~360 images/sec/GPU); the north-star target is >=0.9x.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V100_RESNET50_FP32_IMG_PER_SEC = 360.0


def main():
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    if fluid.core.get_tpu_device_count() > 0:
        place = fluid.TPUPlace(0)
    else:
        place = fluid.CPUPlace()
        batch = min(batch, int(os.environ.get("BENCH_CPU_BATCH", "8")))
        steps = min(steps, 3)

    use_amp = os.environ.get("BENCH_AMP", "1") == "1"
    main_prog, startup, feeds, loss, acc = resnet.build_resnet_train(
        depth=50, class_num=1000, image_size=224, use_amp=use_amp
    )
    exe = fluid.Executor(place)
    exe.run(startup)

    import jax

    dev = fluid.core.get_jax_device(place)
    rs = np.random.RandomState(0)

    def run_at(b):
        # pre-stage the batch on device: the benchmark measures training-step
        # compute (the reference's synthetic-data convention), not host link
        # bandwidth — on this rig H2D rides a network tunnel to the chip
        feed = {
            "img": jax.device_put(
                rs.rand(b, 3, 224, 224).astype("float32"), dev
            ),
            "label": jax.device_put(
                rs.randint(0, 1000, (b, 1)).astype("int64"), dev
            ),
        }
        for _ in range(warmup):
            exe.run(main_prog, feed=feed, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(main_prog, feed=feed, fetch_list=[loss])
        dt = time.perf_counter() - t0
        assert np.isfinite(float(np.asarray(l).ravel()[0]))
        return b * steps / dt

    while True:
        try:
            ips = run_at(batch)
            break
        except Exception as e:  # HBM OOM at this batch — halve and retry
            if ("RESOURCE_EXHAUSTED" not in str(e) and "Out of memory" not in str(e)) or batch <= 32:
                raise
            batch //= 2
            # the failed step donated (deleted) the param buffers — rebuild
            exe = fluid.Executor(place)
            exe.run(startup)

    print(
        json.dumps(
            {
                "metric": "resnet50_train_throughput",
                "value": round(ips, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(ips / V100_RESNET50_FP32_IMG_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
