"""Headline benchmark: ResNet-50 training throughput (images/sec) on one
chip (BASELINE.md metric 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the reference's V100+NCCL path. The
reference publishes no numbers in-repo (BASELINE.md), so the baseline
constant below is the commonly reported PaddlePaddle-era ResNet-50 fp32
V100 figure (~360 images/sec/GPU); the north-star target is >=0.9x.

Hardened against the axon TPU tunnel's transient ``UNAVAILABLE`` errors:
first device contact is a tiny jit with retry+backoff, bring-up
(startup program) retries too, and any terminal failure still emits a
parseable JSON line (value 0 + "error") instead of dying silently.
"""

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V100_RESNET50_FP32_IMG_PER_SEC = 360.0


def _is_transient(e):
    s = str(e)
    return "UNAVAILABLE" in s or "Unavailable" in s or "DEADLINE_EXCEEDED" in s


def _retry(fn, tries=5, base_delay=5.0, tag=""):
    """Run fn() with exponential backoff on transient backend errors."""
    for i in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - backend errors are untyped
            if not _is_transient(e) or i == tries - 1:
                raise
            delay = base_delay * (2**i)
            print(
                "bench: transient backend error at %s (try %d/%d), retrying in %.0fs: %s"
                % (tag or "?", i + 1, tries, delay, str(e)[:200]),
                file=sys.stderr,
            )
            time.sleep(delay)
    raise RuntimeError("unreachable")


def _first_contact(place):
    """Warm the backend with a tiny compile before the big graph."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu.fluid as fluid

    dev = fluid.core.get_jax_device(place)

    def probe():
        x = jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
        y = jax.jit(lambda a: (a @ a).sum())(x)
        y.block_until_ready()
        return float(y)

    _retry(probe, tries=6, base_delay=5.0, tag="first-contact")


def run_bench():
    if os.environ.get("JAX_PLATFORMS"):
        # honor an explicit platform choice even when the axon sitecustomize
        # pinned jax_platforms via config (config beats env in jax)
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    # measured on the axon chip: 1262 img/s @256 vs 1554 img/s @1024 — the
    # bigger batch keeps the MXU fed; OOM-halving below recovers smaller
    # chips automatically
    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    if fluid.core.get_tpu_device_count() > 0:
        place = fluid.TPUPlace(0)
    else:
        place = fluid.CPUPlace()
        batch = min(batch, int(os.environ.get("BENCH_CPU_BATCH", "8")))
        steps = min(steps, 3)

    _first_contact(place)

    use_amp = os.environ.get("BENCH_AMP", "1") == "1"
    # depth/image overrides exist for CPU smoke-testing the bench plumbing;
    # the headline metric is always depth=50 @ 224 (the defaults)
    depth = int(os.environ.get("BENCH_DEPTH", "50"))
    image_size = int(os.environ.get("BENCH_IMG", "224"))
    main_prog, startup, feeds, loss, acc = resnet.build_resnet_train(
        depth=depth, class_num=1000, image_size=image_size, use_amp=use_amp
    )

    import jax

    dev = fluid.core.get_jax_device(place)
    rs = np.random.RandomState(0)

    def bring_up():
        exe = fluid.Executor(place)
        exe.run(startup)
        return exe

    exe = _retry(bring_up, tries=4, base_delay=10.0, tag="startup")

    def run_at(b):
        # pre-stage the batch on device: the benchmark measures training-step
        # compute (the reference's synthetic-data convention), not host link
        # bandwidth — on this rig H2D rides a network tunnel to the chip
        feed = {
            "img": jax.device_put(
                rs.rand(b, 3, image_size, image_size).astype("float32"), dev
            ),
            "label": jax.device_put(
                rs.randint(0, 1000, (b, 1)).astype("int64"), dev
            ),
        }
        for _ in range(warmup):
            exe.run(main_prog, feed=feed, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(main_prog, feed=feed, fetch_list=[loss])
        dt = time.perf_counter() - t0
        assert np.isfinite(float(np.asarray(l).ravel()[0]))
        return b * steps / dt

    while True:
        try:
            ips = _retry(lambda: run_at(batch), tries=3, base_delay=10.0, tag="run")
            return ips, batch
        except Exception as e:  # HBM OOM at this batch — halve and retry
            oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
            if not oom or batch <= 32:
                raise
            batch //= 2
            # the failed step donated (deleted) the param buffers — rebuild
            exe = _retry(bring_up, tries=4, base_delay=10.0, tag="re-startup")


def _arm_watchdog():
    """Guarantee a JSON line even if the TPU tunnel hangs device discovery."""
    import threading

    budget = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    done = threading.Event()

    def fire():
        if done.is_set():  # result already printed — don't clobber it
            return
        print(
            json.dumps(
                {
                    "metric": "resnet50_train_throughput",
                    "value": 0.0,
                    "unit": "images/sec/chip",
                    "vs_baseline": 0.0,
                    "error": "watchdog: no result within %.0fs (backend hang?)"
                    % budget,
                }
            ),
            flush=True,
        )
        os._exit(2)

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()
    return t, done


def main():
    watchdog, done = _arm_watchdog()
    try:
        ips, batch = run_bench()
        done.set()
        watchdog.cancel()
        print(
            json.dumps(
                {
                    "metric": "resnet50_train_throughput",
                    "value": round(ips, 2),
                    "unit": "images/sec/chip",
                    "vs_baseline": round(ips / V100_RESNET50_FP32_IMG_PER_SEC, 3),
                    "batch": batch,
                }
            )
        )
    except Exception:
        done.set()
        watchdog.cancel()
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "resnet50_train_throughput",
                    "value": 0.0,
                    "unit": "images/sec/chip",
                    "vs_baseline": 0.0,
                    "error": traceback.format_exc().strip().splitlines()[-1][:300],
                }
            )
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
