"""Headline benchmarks: ResNet-50 training throughput (BASELINE.md metric
1) and BERT-base fine-tune throughput (metric 2) on one chip.

Prints one JSON line per metric, ResNet-50 (the headline) first:
  {"metric": "resnet50_train_throughput", "value", "unit", "vs_baseline", ...}
  {"metric": "bert_base_finetune_throughput", ...}
  {"metric": "gpt2_small_lm_throughput", ...}   (bonus; only when banked)

``vs_baseline`` compares against the reference's V100+NCCL path. The
reference publishes no numbers in-repo (BASELINE.md), so the baseline
constants below are the commonly reported PaddlePaddle-era V100 figures
(~360 images/sec ResNet-50 fp32, ~40 seq/s BERT-base seq128); the
north-star target is >=0.9x.

Architecture (hardened for the axon TPU tunnel, which can HANG — not
raise — inside device discovery or compilation, where no in-process
watchdog can interrupt the C++ call):

- The parent process never imports jax. It spawns one child process per
  attempt with a HARD wall-clock timeout; on expiry the whole child
  process group is SIGKILLed.
- Cheap-first ladder (VERDICT r3 #1): batch 64 first (small compile,
  short slot) to BANK a TPU number, then escalate 256 -> 1024 only
  after a success. Results accumulate; the best per metric is emitted
  at the end, so a later failure can never lose a banked number.
- Every child enables a persistent XLA compilation cache
  (.jax_cache/, git-ignored) so a retry after a tunnel hiccup — or the
  driver's end-of-round run after an interactive warm-up — skips
  recompilation entirely.
- Hang detection: if the FIRST TPU attempt is killed before its
  "probe ok" heartbeat (the r3 failure mode: hung at device discovery),
  the parent stops trusting the tunnel, banks degraded CPU fallbacks
  immediately, then spreads short (150s) TPU retries across the rest
  of the watchdog window in case the tunnel comes back.
- The child emits "HB <phase> ..." heartbeat lines on stderr at every
  phase transition (probe / build / startup / warmup / step k/N); the
  parent relays them with elapsed timestamps, so a tail of the driver
  log shows exactly where a dead attempt died.
- All slots are scheduled against the driver's 1500s watchdog minus a
  60s margin; an attempt never starts unless its slot fits.
"""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V100_RESNET50_FP32_IMG_PER_SEC = 360.0
METRIC = "resnet50_train_throughput"
UNIT = "images/sec/chip"

# --------------------------------------------------------------------------
# persistent TPU results-bank (VERDICT r4 task 1)
#
# Any successful TPU measurement — this run, a previous driver run, or the
# background watcher's live-window playbook (tools/tpu_watcher.py) — is
# recorded in the committed BENCH_BANK.json with its git sha and UTC
# timestamp. When every live TPU attempt in a run dies (the axon tunnel
# has hung through entire rounds), the emitted line falls back to the
# banked number with "banked": true + provenance instead of a meaningless
# CPU figure; a CPU fallback is only emitted when the bank is empty, and
# then with vs_baseline: null (a CPU number has no defensible relation to
# the V100 baseline).
# --------------------------------------------------------------------------

BANK_PATH = os.environ.get(
    "BENCH_BANK_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BANK.json"),
)


def best_window_rate(samples, min_window_s):
    """Best (events/sec) over any sample window spanning at least
    ``min_window_s``, from a monotone list of (t, cumulative_count)
    pairs; falls back to the full span when no window is long enough.
    The load-robust throughput estimator shared by the decode probe and
    the BENCH_DECODE rung: external load only ever subtracts throughput,
    so the max window is the undisturbed steady-state figure without the
    admission ramp / drain tail. The O(n^2) pairwise scan is fine for
    the sample counts involved (sub-second polling over seconds-long
    runs — hundreds of samples)."""
    best = 0.0
    for i in range(len(samples)):
        for j in range(i + 1, len(samples)):
            dt = samples[j][0] - samples[i][0]
            if dt >= min_window_s:
                best = max(best, (samples[j][1] - samples[i][1]) / dt)
    if best == 0.0 and len(samples) >= 2:
        dt = samples[-1][0] - samples[0][0]
        best = (samples[-1][1] - samples[0][1]) / max(dt, 1e-6)
    return best


def load_bank():
    try:
        with open(BANK_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _bank_entry(line):
    """Bank entry from an emit line: keep the measurement facts, drop the
    run-relative fields (vs_baseline is recomputed at emit time)."""
    keep = ("metric", "value", "unit", "batch", "device", "seq_len",
            "remat", "flash_attention", "hostfeed", "plan_hit_rate",
            "h2d_overlapped", "serving", "offline_rps", "p99_ms",
            "batch_fill", "bucket_hit_rate", "clients",
            # decode (BENCH_DECODE=1) rung facts: tokens/sec/user is the
            # banked value; the aggregate rate and engine geometry ride
            # along for context
            "decode", "streams", "tok_per_sec", "max_len", "max_new",
            # prefix rung (gpt_decode_prefix): prefix_cache is the
            # bank_best guard flag; TTFT + share/hit-rate are the facts
            # the rung exists to bank
            "prefix_cache", "ttft_ms", "prefix_share", "prefix_hits",
            "prefix_hit_rate", "cached_prefix_tokens",
            # decode engine v2 rungs: gpt_decode_paged banks the seq-4k
            # block-table rate with its pool-byte budget (the claim is
            # "longer streams at UNCHANGED pool bytes"); gpt_decode_spec
            # banks the speculative rate with its width-1 baseline,
            # controlled drafter accuracy, and measured acceptance
            "paged", "paged_block", "pool_blocks", "pool_bytes",
            "pool_anchor_len", "oom_sheds",
            "spec", "spec_tokens", "spec_speedup", "spec_acceptance",
            "spec_parity", "draft_accuracy", "baseline_tok_per_sec_user",
            # tensor-parallel rung (gpt_decode_tp): tp is the bank_best
            # guard flag; tp_degree is the mesh width the rate was
            # measured at (a TP=2 rate is a different machine budget —
            # it must never replace the single-device decode headline)
            "tp", "tp_degree",
            # per-rung cost census (observability/xla_stats): the
            # compiled step's FLOP/HBM-byte budget banks alongside the
            # throughput so PERF.md's bytes-budget table has provenance
            # and future perf PRs have a regression baseline;
            # census_source says where the numbers came from
            # ("live_census" vs a hand-recorded hlo_scan artifact)
            "flops", "bytes_accessed", "out_bytes", "census_source")
    return {k: line[k] for k in keep if k in line}


def bank_write(slot, entry):
    """Record a successful TPU measurement under ``slot`` (bank-the-best:
    a slower re-measurement never overwrites a faster banked one).
    Locked read-modify-write: the background watcher (tools/tpu_watcher.py)
    and a driver/interactive bench run may bank concurrently.
    Returns True if the bank changed."""
    import fcntl

    with open(BANK_PATH + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        bank = load_bank()
        prev = bank.get(slot)
        if prev is not None and prev.get("value", 0.0) >= entry["value"]:
            return False
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True,
                text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=10,
            ).stdout.strip() or "unknown"
        except (OSError, subprocess.SubprocessError):
            sha = "unknown"
        # a faster run whose census was unavailable (census flag off, or
        # headline_census failed) must not erase the slot's banked
        # flops/bytes baseline — PERF.md's bytes-budget table depends on
        # it surviving every re-bank. Carry is ALL-or-nothing: splicing
        # one prior field into a fresh partial census would bank a
        # mixed-run baseline under a single census_source label
        census_fields = ("flops", "bytes_accessed", "out_bytes")
        carried = {}
        if prev is not None and not any(k in entry for k in census_fields):
            carried = {
                k: prev[k]
                for k in census_fields + ("census_source",)
                if k in prev
            }
        bank[slot] = dict(
            entry,
            git_sha=sha,
            measured_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            **carried,
        )
        tmp = BANK_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bank, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, BANK_PATH)
    return True


def bank_best(prefix):
    """Best banked TPU entry whose slot starts with ``prefix`` (or None).
    Host-fed rungs are a SEPARATE convention (the measured rate includes
    host decode/H2D): a prefix match must never promote one to a
    device-resident headline — ask for them explicitly via a prefix
    containing 'hostfeed'. Serving rungs (BENCH_SERVING=1: requests/sec
    through the dynamic-batching runtime, a different metric entirely)
    are guarded the same way — only a prefix containing 'serving' sees
    them. Decode rungs (tokens/sec/user) need 'decode' in the prefix,
    and the BENCH_DECODE prefix-cache rung (tokens/sec/user at ~90%
    prefix share — an amortized metric a cold-prompt decode headline
    must never inherit) additionally needs 'prefix'. The tensor-parallel
    rung (gpt_decode_tp: the same per-user rate but spread over a TP
    mesh — a different machine budget) is likewise only visible to a
    prefix containing 'tp'."""
    cands = [
        (slot, e)
        for slot, e in load_bank().items()
        if slot.startswith(prefix) and e.get("device") == "tpu"
        and ("hostfeed" in prefix or not e.get("hostfeed"))
        and ("serving" in prefix or not e.get("serving"))
        and ("decode" in prefix or not e.get("decode"))
        and ("prefix" in prefix or not e.get("prefix_cache"))
        and ("paged" in prefix or not e.get("paged"))
        and ("spec" in prefix or not e.get("spec"))
        and ("tp" in prefix or not e.get("tp"))
    ]
    if not cands:
        return None, None
    return max(cands, key=lambda kv: kv[1].get("value", 0.0))


def probe_accelerator(timeout_s=100):
    """True iff a non-cpu jax backend answers device discovery AND a tiny
    jit within ``timeout_s``, probed in a KILLABLE child so a hung axon
    tunnel costs a bounded wait instead of blocking this process's
    backend init forever. Own process group + killpg + DEVNULL streams:
    SIGKILLing a child that spawned tunnel-helper grandchildren must not
    leave the caller blocked on an inherited pipe. The child enables the
    shared persistent compilation cache, so on a healthy tunnel the tiny
    compile is warm after the first ever probe and the timeout only
    trips for genuinely dead/wedged tunnels."""
    import signal

    src = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax, bench\n"
        "bench.enable_compilation_cache(jax)\n"
        "assert any(d.platform != 'cpu' for d in jax.devices())\n"
        "import jax.numpy as jnp\n"
        "jax.jit(lambda a: (a @ a).sum())("
        "jnp.ones((128, 128), jnp.bfloat16)).block_until_ready()\n"
    ) % os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.Popen(
        [sys.executable, "-c", src],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        rc = -9
    return rc == 0


def honor_jax_platforms(jax):
    """Make an explicit JAX_PLATFORMS env choice actually take effect: the
    axon sitecustomize pins jax_platforms="axon,cpu" via config, which
    BEATS the env var — and a hung tunnel then blocks backend init forever
    before the cpu fallback can engage. Call before any backend
    initializes. No-op when the env var is unset (live-TPU intent)."""
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def enable_compilation_cache(jax):
    """Persistent XLA compilation cache shared by every bench child, so
    retries (and the driver's end-of-round run) skip recompilation."""
    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        print("HB compilation cache unavailable: %s" % e, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# child: one benchmark attempt (fixed config, no retries — parent owns those)
# --------------------------------------------------------------------------


def _hb(msg):
    print("HB %s" % msg, file=sys.stderr, flush=True)


def _child_fail(kind, msg):
    """Report a classified failure to the parent and exit nonzero."""
    print("CHILDERR " + json.dumps({"kind": kind, "msg": str(msg)[:300]}), flush=True)
    sys.exit(1)


def serving_child_main(cfg):
    """BENCH_SERVING=1 rung: offline-batch vs dynamic-batch serving
    throughput + p99 on the GPT-2 export. One request = one seq_len
    sequence; 'offline' runs pre-stacked full batches through
    predictor.run (the upper bound dynamic batching chases), 'dynamic'
    drives the InferenceServer with closed-loop concurrent clients.
    Banked under the 'gpt_serving' slot, never promoted to a headline
    (bank_best guards on the serving flag, same as the hostfeed rung)."""
    import tempfile
    import threading

    t_start = time.time()
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]

    import jax

    honor_jax_platforms(jax)
    enable_compilation_cache(jax)

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference, serving
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_infer

    _hb("probe start (device discovery)")
    if cfg["platform"] == "cpu":
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        _child_fail("no_tpu", "no TPU device visible to this child")
    else:
        device = "tpu"
    _hb("probe ok %.1fs device=%s" % (time.time() - t_start, device))

    seq_len = cfg.get("seq_len", 128)
    max_batch = cfg.get("batch", 8)
    clients = cfg.get("clients", 2 * max_batch)
    gcfg = GPTConfig(
        vocab_size=cfg.get("vocab", 50257),
        hidden_size=cfg.get("hidden", 768),
        num_layers=cfg.get("layers", 12),
        num_heads=cfg.get("heads", 12),
        intermediate_size=cfg.get("hidden", 768) * 4,
        is_test=True,
    )
    t0 = time.time()
    _hb("build start (GPT infer graph + export)")
    main_prog, startup, feed_names, logits = build_gpt_infer(gcfg, seq_len)
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
    # a GPT-2-small export is ~0.5 GB: clean it up even on failure, or
    # repeated runs fill /tmp on a long-lived TPU host
    import shutil

    export_dir = tempfile.mkdtemp(prefix="bench_serving_")
    try:
        with fluid.scope_guard(scope):
            fluid.io.save_inference_model(
                export_dir, feed_names,
                [main_prog.global_block().var(logits.name)], exe,
                main_program=main_prog,
            )
        _hb("build ok %.1fs" % (time.time() - t0))
        _serving_measure(cfg, inference, serving, np, export_dir, device,
                         gcfg, seq_len, max_batch, clients)
    finally:
        shutil.rmtree(export_dir, ignore_errors=True)


def _serving_measure(cfg, inference, serving, np, export_dir, device, gcfg,
                     seq_len, max_batch, clients):
    """Measurement body of the serving rung (export_dir cleanup owned by
    serving_child_main)."""
    import threading

    rs = np.random.RandomState(0)
    one = [
        rs.randint(0, gcfg.vocab_size, (1, seq_len, 1)).astype("int64"),
        np.arange(seq_len, dtype="int64").reshape(1, seq_len, 1),
        np.ones((1, seq_len, 1), dtype="float32"),
    ]
    stacked = [np.repeat(a, max_batch, axis=0) for a in one]

    t0 = time.time()
    _hb("offline warmup start (batch-%d compile)" % max_batch)
    offline_pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(export_dir)
    )
    offline_pred.run(stacked)
    _hb("offline warmup ok %.1fs" % (time.time() - t0))
    steps = cfg.get("steps", 10)
    t0 = time.perf_counter()
    for _ in range(steps):
        offline_pred.run(stacked)
    offline_rps = steps * max_batch / (time.perf_counter() - t0)
    _hb("offline ok %.1f req/s" % offline_rps)

    t0 = time.time()
    _hb("server warmup start (bucket ladder compiles)")
    server_pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(export_dir)
    )
    server = serving.InferenceServer(
        server_pred, max_batch_size=max_batch,
        batch_timeout_ms=cfg.get("batch_timeout_ms", 8.0),
        queue_depth=4 * clients, num_workers=cfg.get("workers", 1),
    ).start(warmup_inputs=one)
    _hb("server warmup ok %.1fs" % (time.time() - t0))

    per_client = cfg.get("requests_per_client", 2 * steps)
    errors = []

    def client_loop():
        try:
            for _ in range(per_client):
                server.infer(one, deadline_ms=120000)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=client_loop) for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    stats = server.stats()
    server.stop()
    if errors:
        _child_fail("other", "serving clients failed: %r" % errors[:2])
    rps = clients * per_client / dt
    _hb("dynamic ok %.1f req/s fill=%.2f" % (rps, stats.batch_fill_ratio))
    print("RESULT " + json.dumps({
        "rps": rps,
        "offline_rps": offline_rps,
        "p99_ms": stats.latency_ms["p99"],
        "batch_fill": stats.batch_fill_ratio,
        "bucket_hit_rate": stats.bucket_hit_rate,
        "plan_misses_after_warm": stats.plan_cache_misses,
        "clients": clients,
        "device": device,
    }), flush=True)


def decode_child_main(cfg):
    """BENCH_DECODE=1 rung: autoregressive tokens/sec through the
    KV-cache continuous-batching engine (paddle_tpu/serving/decode.py)
    at N concurrent streams. Headline is tokens/sec/USER (= total
    decode throughput / streams) — the metric the ROADMAP's
    "millions of users" serving item is denominated in. Banked under
    'gpt_decode', never promoted to a training headline. The decode-step
    program's flops/bytes census rides along where cost analysis
    permits (flash-decode engages the Pallas kernel, which cost
    analysis cannot see inside — those rungs bank without a census,
    like every other flash rung)."""
    t_start = time.time()
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]
    tp = int(cfg.get("tp", 0) or 0)
    if tp > 1 and cfg["platform"] == "cpu":
        # tp rung on the CPU backend: fork the host into tp virtual
        # devices before jax initializes (same lever the SPMD probe and
        # test harness use)
        cur = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in cur:
            os.environ["XLA_FLAGS"] = (
                cur + " --xla_force_host_platform_device_count=%d" % tp
            ).strip()

    import jax

    honor_jax_platforms(jax)
    enable_compilation_cache(jax)

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import profiler
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_infer
    from paddle_tpu.observability import xla_stats as _xla_stats
    from paddle_tpu.serving.decode import DecodeEngine

    _hb("probe start (device discovery)")
    if cfg["platform"] == "cpu":
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        _child_fail("no_tpu", "no TPU device visible to this child")
    else:
        device = "tpu"
    _hb("probe ok %.1fs device=%s" % (time.time() - t_start, device))

    streams = cfg.get("streams", 8)
    max_len = cfg.get("max_len", 256)
    # decode engine v2 knobs: paged_block > 0 routes through the
    # block-table runtime; spec_tokens > 1 additionally arms the k-token
    # speculative verify (spec rung runs a width-1 baseline first)
    paged_block = int(cfg.get("paged_block", 0) or 0)
    spec_k = int(cfg.get("spec_tokens", 0) or 0)
    gcfg = GPTConfig(
        vocab_size=cfg.get("vocab", 50257),
        hidden_size=cfg.get("hidden", 768),
        num_layers=cfg.get("layers", 12),
        num_heads=cfg.get("heads", 12),
        intermediate_size=cfg.get("hidden", 768) * 4,
        # spec verify embeds positions up to max_len + k - 2
        max_position_embeddings=max(max_len + max(spec_k - 1, 0), 256),
        is_test=True,
        use_flash_attention=bool(cfg.get("flash")),
    )
    t0 = time.time()
    _hb("build start (GPT infer graph for params)")
    with fluid.unique_name.guard():
        main_prog, startup, _feeds, _logits = build_gpt_infer(gcfg, max_len)
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
    _hb("params ok %.1fs" % (time.time() - t0))

    t0 = time.time()
    _hb("engine warmup start (prefill ladder + decode step compiles)")
    prompt_len = cfg.get("prompt_len", 32)
    rs = np.random.RandomState(0)
    prefix_cache = bool(cfg.get("prefix_cache"))
    eng_kw = {}
    shared = None
    if prefix_cache:
        # BENCH_DECODE prefix rung: every request shares a system-prompt
        # prefix of ~prefix_share of the prompt (block-aligned); the
        # store is sized generously so the trial measures reuse, not
        # eviction churn
        from paddle_tpu.models.gpt import prefix_block_bytes

        block = int(cfg.get("prefix_block", 16))
        share = float(cfg.get("prefix_share", 0.9))
        # block-aligned, and capped at prompt_len - 1 so the suffix is
        # never empty (mirrors the engine's len-1 lookup cap); a prompt
        # too short to hold even one shared block is a config error,
        # reported instead of crashing mk_prompt with a negative size
        shared_len = min(int(prompt_len * share) // block * block,
                         (prompt_len - 1) // block * block)
        if shared_len < block:
            _child_fail(
                "config",
                "prefix rung needs prompt_len > prefix_block "
                "(prompt_len %d, block %d, share %.2f)"
                % (prompt_len, block, share),
            )
        shared = list(rs.randint(0, gcfg.vocab_size, shared_len))
        blocks = 8 * (shared_len // block)
        eng_kw = dict(
            prefix_block=block,
            prefix_cache_mb=blocks * prefix_block_bytes(gcfg, block)
            / 2.0 ** 20,
        )
    pool_blocks = 0
    pool_bytes = None
    if paged_block:
        from paddle_tpu.models.gpt import paged_block_bytes

        # pool sized to the HBM an anchor-geometry LEGACY engine spends
        # on contiguous [slots, anchor_len] rows (+ the sink block) —
        # the seq-4k rung's claim is "longer streams at UNCHANGED pool
        # bytes", so the anchor is the budget, not max_len
        anchor = int(cfg.get("pool_anchor_len", 0) or 0)
        if anchor:
            pool_blocks = streams * anchor // paged_block + 1
        eng_kw.update(block_size=paged_block, pool_blocks=pool_blocks)
    if tp > 1:
        # tensor-parallel rung: every decode/prefill/paged program runs
        # GSPMD-sharded over a {"model": tp} mesh (KV pools partitioned
        # on the heads axis, block tables replicated)
        if jax.device_count() < tp:
            _child_fail(
                "config",
                "tp rung needs >= %d devices, backend has %d"
                % (tp, jax.device_count()),
            )
        eng_kw["tp"] = tp

    n_requests = cfg.get("requests", 4 * streams)
    max_new = cfg.get("max_new", 64)

    def mk_prompt():
        if shared is None:
            return list(rs.randint(0, gcfg.vocab_size, prompt_len))
        return shared + list(rs.randint(
            0, gcfg.vocab_size, prompt_len - len(shared)))

    # fixed prompt pool (cycled) so the spec rung's replay-drafter phase
    # sees the exact workload its width-1 baseline recorded
    prompt_pool = [mk_prompt() for _ in range(2 * streams)]

    def run_workload(engine):
        handles = [
            engine.generate(prompt_pool[i % len(prompt_pool)],
                            max_new_tokens=max_new)
            for i in range(n_requests)
        ]
        samples = [(time.perf_counter(),
                    profiler.get_counters().get("decode_tokens", 0))]
        while not all(h.done for h in handles):
            time.sleep(0.1)
            samples.append((time.perf_counter(),
                            profiler.get_counters().get("decode_tokens", 0)))
        samples.append((time.perf_counter(),
                        profiler.get_counters().get("decode_tokens", 0)))
        for h in handles:
            h.tokens(timeout=600)
        # best >=2 s window = steady-state rate without ramp/drain tails
        return best_window_rate(samples, 2.0), handles

    base_kw = dict(gcfg=gcfg, scope=scope, slots=streams, max_len=max_len,
                   prefill_buckets=[prompt_len, max_len],
                   param_program=main_prog)
    spec_facts = {}
    drafter = None
    if spec_k > 1:
        # phase 1 of the spec rung: the SAME paged geometry at width 1.
        # Greedy decode is deterministic, so its streams double as the
        # recorded continuations the replay drafter proposes in phase 2
        # at a controlled accuracy — the banked speedup measures the
        # k-token verify/rollback machinery at that acceptance, not
        # drafter luck on random weights
        _hb("spec baseline start (width-1 paged engine)")
        kw = dict(base_kw)
        g = kw.pop("gcfg")
        base_eng = DecodeEngine(g, **kw, **dict(eng_kw, spec_tokens=0))\
            .start()
        try:
            base_tps, base_handles = run_workload(base_eng)
        finally:
            base_eng.stop()
        recorded = {}
        for h in base_handles:
            p = list(h.prompt_ids)
            recorded[tuple(p)] = p + h.tokens(timeout=10)
        accuracy = float(cfg.get("draft_accuracy", 0.9))
        drs = np.random.RandomState(11)

        def drafter(hist, k):
            full = recorded.get(tuple(hist[:prompt_len]))
            if full is None:
                return [0] * k
            d = list(full[len(hist):len(hist) + k])
            d += [0] * (k - len(d))
            return [t if drs.random_sample() < accuracy
                    else (int(t) + 1) % gcfg.vocab_size for t in d]

        eng_kw["spec_tokens"] = spec_k
        spec_facts = {
            "baseline_tok_per_sec_user": round(base_tps / streams, 2),
            "draft_accuracy": accuracy,
        }
        _hb("spec baseline ok %.1f tok/s" % base_tps)

    engine = DecodeEngine(
        gcfg, scope=scope, slots=streams, max_len=max_len,
        prefill_buckets=[prompt_len, max_len], param_program=main_prog,
        drafter=drafter, **eng_kw
    ).start()
    _hb("engine warmup ok %.1fs" % (time.time() - t0))
    try:
        tok_s, handles = run_workload(engine)
        stats = engine.stats()
        if spec_k > 1:
            base_u = spec_facts["baseline_tok_per_sec_user"]
            spec_facts.update({
                "spec_speedup": round(
                    tok_s / streams / max(base_u, 1e-9), 2),
                "spec_acceptance": round(
                    stats.get("spec_acceptance", 0.0), 3),
                # greedy determinism: the spec streams must be byte-
                # identical to the width-1 recordings
                "spec_parity": all(
                    list(h.prompt_ids) + h.tokens(timeout=10)
                    == recorded.get(tuple(h.prompt_ids))
                    for h in handles
                ),
            })
        census = None
        if not cfg.get("flash") and not paged_block:
            # census of the DECODE-STEP program specifically — the
            # generic heaviest-program headline would pick a prefill
            # bucket, whose bytes budget is not the serving steady state
            # (the paged step is fed block tables; its census rides the
            # same xla_stats path but is not this rung's banked fact)
            dmain, dfetch = engine.session._decode
            fp = _xla_stats.fingerprint(_xla_stats.make_key(
                dmain, ["step_ids", "step_pos", "key_bias"], [dfetch]
            ))
            census = _xla_stats.census_by_key().get(fp)
        if paged_block:
            pool_blocks = engine.session.pool_blocks
            pool_bytes = pool_blocks * paged_block_bytes(gcfg, paged_block)
    finally:
        engine.stop()
    _hb("decode ok %.1f tok/s at %d streams" % (tok_s, streams))
    result = {
        "tok_per_sec": tok_s,
        "tok_per_sec_user": tok_s / streams,
        "streams": streams,
        "max_len": max_len,
        "max_new": max_new,
        "requests": stats["requests"],
        "steps": stats["steps"],
        "device": device,
    }
    if paged_block:
        result.update({
            "paged": True,
            "paged_block": paged_block,
            "pool_blocks": pool_blocks,
            "pool_bytes": int(pool_bytes),
            "pool_anchor_len": int(cfg.get("pool_anchor_len", 0) or 0),
            "oom_sheds": stats.get("oom_sheds", 0),
        })
    if spec_k > 1:
        result.update(spec_facts)
        result.update({"spec": True, "spec_tokens": spec_k})
    if tp > 1:
        result.update({"tp": True, "tp_degree": tp})
    if prefix_cache:
        hit_ttfts = [h.ttft_ms for h in handles
                     if getattr(h, "cached_prefix_tokens", 0) > 0
                     and h.ttft_ms is not None]
        result.update({
            "prefix_share": round(len(shared) / prompt_len, 3),
            "prefix_hits": stats.get("prefix_hits", 0),
            "prefix_hit_rate": round(
                stats.get("prefix_hits", 0) / max(1, n_requests), 3),
            "cached_prefix_tokens": stats.get("prefix_cached_tokens", 0),
            "ttft_ms": round(float(np.mean(hit_ttfts)), 2)
            if hit_ttfts else None,
        })
    if census is not None:
        for k in ("flops", "bytes_accessed", "out_bytes"):
            if census.get(k) is not None:
                result[k] = census[k]
        result["census_source"] = "live_census"
    print("RESULT " + json.dumps(result), flush=True)


def child_main(cfg):
    if cfg.get("serving"):
        return serving_child_main(cfg)
    if cfg.get("decode"):
        return decode_child_main(cfg)
    t_start = time.time()
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]

    import jax

    honor_jax_platforms(jax)
    enable_compilation_cache(jax)

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    _hb("probe start (device discovery + tiny compile)")
    if cfg["platform"] == "cpu":
        place = fluid.CPUPlace()
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        # fail fast rather than burn the hard timeout running a TPU-sized
        # batch on the CPU backend
        _child_fail("no_tpu", "no TPU device visible to this child")
    else:
        place = fluid.TPUPlace(0)
        device = "tpu"
    dev = fluid.core.get_jax_device(place)
    import jax.numpy as jnp

    x = jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
    y = jax.jit(lambda a: (a @ a).sum())(x)
    y.block_until_ready()
    _hb("probe ok %.1fs device=%s" % (time.time() - t_start, device))

    batch = cfg["batch"]
    steps = cfg["steps"]
    warmup = cfg["warmup"]
    depth = cfg["depth"]
    image_size = cfg["image_size"]

    t0 = time.time()
    _hb("build start (program construction)")
    main_prog, startup, feeds, loss, acc = resnet.build_resnet_train(
        depth=depth,
        class_num=1000,
        image_size=image_size,
        use_amp=cfg["amp"],
        recompute=bool(cfg.get("remat")),
    )
    _hb("build ok %.1fs" % (time.time() - t0))

    t0 = time.time()
    _hb("startup start (param init compile+run)")
    exe = fluid.Executor(place)
    exe.run(startup)
    _hb("startup ok %.1fs" % (time.time() - t0))

    rs = np.random.RandomState(0)
    hostfeed = bool(cfg.get("hostfeed"))
    if hostfeed:
        # host-fed mode (BENCH_HOSTFEED=1): every batch is GENERATED on
        # the host and travels through the double-buffered io_pipeline, so
        # the measured rate includes host decode + H2D — overlapped behind
        # compute by the pipeline instead of serialized before each step.
        # This is the rung that proves the overlap claim on hardware; the
        # device-resident mode below stays the headline convention.
        from paddle_tpu.fluid import profiler as _profiler

        n_batches = warmup + 2 + steps

        def _host_batches():
            hrs = np.random.RandomState(1)
            for _ in range(n_batches):
                yield {
                    "img": hrs.rand(batch, 3, image_size, image_size)
                    .astype("float32"),
                    "label": hrs.randint(0, 1000, (batch, 1))
                    .astype("int64"),
                }

        loader = fluid.DataLoader.from_generator(
            capacity=4, use_double_buffer=True
        )
        loader.set_batch_generator(_host_batches, places=[place])
        feed_iter = iter(loader)

        def next_feed():
            return next(feed_iter)

        _hb("hostfeed pipeline ready (double-buffered)")
    else:
        # pre-stage the batch on device: this mode measures training-step
        # compute (the reference's synthetic-data convention), not host
        # link bandwidth — on this rig H2D rides a network tunnel
        feed = {
            "img": jax.device_put(
                rs.rand(batch, 3, image_size, image_size).astype("float32"),
                dev,
            ),
            "label": jax.device_put(
                rs.randint(0, 1000, (batch, 1)).astype("int64"), dev
            ),
        }

        def next_feed():
            return feed

    t0 = time.time()
    _hb("warmup start (%d steps, includes main-graph compile)" % warmup)
    for i in range(warmup):
        exe.run(main_prog, feed=next_feed(), fetch_list=[loss])
        _hb("warmup step %d/%d done %.1fs" % (i + 1, warmup, time.time() - t0))
    # the executor cache key includes the fetch list, so the fetch-free
    # variant used by the timed loop must be compiled here, not inside it;
    # the follow-up fetching run DRAINS the async queue so none of that
    # work leaks into the timed window
    exe.run(main_prog, feed=next_feed(), fetch_list=[])
    exe.run(main_prog, feed=next_feed(), fetch_list=[loss])
    _hb("warmup fetch-free variant done %.1fs" % (time.time() - t0))

    c0 = _profiler.get_counters() if hostfeed else {}
    _hb("timed run start (%d steps)" % steps)
    t0 = time.perf_counter()
    l = None
    for i in range(steps):
        # fetch the loss only on the final step: fetching synchronizes
        # host<->device every iteration, which on a tunneled chip serializes
        # the pipeline (VERDICT r2 weak #2)
        fetches = [loss] if i == steps - 1 else []
        out = exe.run(main_prog, feed=next_feed(), fetch_list=fetches)
        if fetches:
            (l,) = out
    lval = float(np.asarray(l).ravel()[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(lval), "non-finite loss %r" % lval
    ips = batch * steps / dt
    _hb("timed run ok %.2fs loss=%.4f ips=%.1f" % (dt, lval, ips))

    result = {"ips": ips, "device": device, "loss": lval}
    # bank the rung's cost census: the executor recorded cost analysis +
    # HLO op counts for every executable it compiled this run (free at
    # compile time); the heaviest program key IS the training step
    try:
        from paddle_tpu.observability import xla_stats as _xla_stats

        _xla_stats.attach_headline_census(result)
    except Exception as e:  # census must never sink a measurement
        _hb("census unavailable: %s" % e)
    if hostfeed:
        # steady-state plan hit rate over the timed window (delta vs the
        # pre-loop snapshot); the staging count covers the whole run —
        # the pipeline legitimately runs ahead during warmup
        c = _profiler.get_counters()
        hits = c.get("executor_plan_cache_hits", 0) - c0.get(
            "executor_plan_cache_hits", 0
        )
        misses = c.get("executor_plan_cache_misses", 0) - c0.get(
            "executor_plan_cache_misses", 0
        )
        result["hostfeed"] = True
        result["plan_hit_rate"] = round(hits / max(hits + misses, 1), 4)
        result["h2d_overlapped"] = c.get("io_pipeline_h2d_batches", 0)
    print("RESULT " + json.dumps(result), flush=True)


def _child_entry(cfg):
    try:
        child_main(cfg)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 - classify for the parent
        s = str(e)
        if "RESOURCE_EXHAUSTED" in s or "Out of memory" in s or "out of memory" in s:
            kind = "oom"
        elif "UNAVAILABLE" in s or "Unavailable" in s or "DEADLINE_EXCEEDED" in s:
            kind = "transient"
        else:
            kind = "other"
        import traceback

        traceback.print_exc(file=sys.stderr)
        _child_fail(kind, s)


# --------------------------------------------------------------------------
# parent: attempt schedule, hard timeouts, heartbeat relay
# --------------------------------------------------------------------------


def _base_cfg():
    return {
        "steps": int(os.environ.get("BENCH_STEPS", "20")),
        "warmup": int(os.environ.get("BENCH_WARMUP", "3")),
        "depth": int(os.environ.get("BENCH_DEPTH", "50")),
        "image_size": int(os.environ.get("BENCH_IMG", "224")),
        "amp": os.environ.get("BENCH_AMP", "1") == "1",
        # rematerialize residual-block activations (PERF.md lever 1):
        # trades recompute FLOPs for the bandwidth-dominant activation
        # writes on the HBM-bound step
        "remat": os.environ.get("BENCH_REMAT", "0") == "1",
        # host-fed rung: batches generated on the host per step and
        # streamed through the double-buffered io_pipeline (the overlap
        # lever); the default stays the device-resident convention
        "hostfeed": os.environ.get("BENCH_HOSTFEED", "0") == "1",
        "platform": "",
    }


def _run_attempt(label, cfg, timeout, deadline, script=None):
    """Spawn one child attempt; kill its whole process group on timeout.
    Returns (result_dict_or_None, kind, error_str, probe_ok). kind in
    {"", "killed", "no_tpu", "oom", "transient", "other", "skipped"};
    probe_ok is True once the child's device-discovery probe heartbeat
    was seen (False on a pre-probe hang — the r3 tunnel failure mode).
    ``script`` lets sibling harnesses (bench_bert.py) reuse this exact
    streaming-relay + kill-timer machinery with their own --child entry."""
    budget = min(timeout, deadline - time.time())
    if budget < 30:
        return None, "skipped", "skipped: <30s left in budget", False
    t0 = time.time()
    print(
        "bench[%s]: starting (hard timeout %.0fs)" % (label, budget),
        file=sys.stderr,
        flush=True,
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            script or os.path.abspath(__file__),
            "--child",
            json.dumps(cfg),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,  # own process group => killable even if wedged in C++
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    result, childerr, lines = None, None, []
    killed = False
    probe_ok = False

    import threading

    def _kill():
        nonlocal killed
        killed = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass

    timer = threading.Timer(budget, _kill)
    timer.daemon = True
    timer.start()
    try:
        for line in proc.stdout:
            line = line.rstrip("\n")
            if line.startswith("RESULT "):
                try:
                    result = json.loads(line[len("RESULT ") :])
                except ValueError:
                    lines.append(line)
            elif line.startswith("CHILDERR "):
                try:
                    childerr = json.loads(line[len("CHILDERR ") :])
                except ValueError:
                    lines.append(line)
            else:
                lines.append(line)
                if "probe ok" in line:
                    probe_ok = True
                # relay heartbeats (and any backend noise) with timestamps
                print(
                    "bench[%s +%.0fs]: %s" % (label, time.time() - t0, line[:300]),
                    file=sys.stderr,
                    flush=True,
                )
        proc.wait()
    finally:
        timer.cancel()
    if result is not None:
        # a valid result beats a kill flag set in the exit race window
        return result, "", "", probe_ok
    if childerr is not None:
        return None, childerr.get("kind", "other"), childerr.get("msg", ""), probe_ok
    if killed:
        last = lines[-1] if lines else "(no output)"
        return (
            None,
            "killed",
            "killed at %.0fs hard timeout; last: %s" % (budget, last),
            probe_ok,
        )
    last = next(
        (l for l in reversed(lines) if "Error" in l or "error" in l),
        lines[-1] if lines else "(no output)",
    )
    return (
        None,
        "other",
        "exit rc=%d without result; last: %s" % (proc.returncode, last[:300]),
        probe_ok,
    )


def _emit(out):
    print(json.dumps(out), flush=True)


# Per-seq-len V100 fp32 BERT-base fine-tune baselines (BASELINE.md metric
# 2 provenance note): seq128 is the commonly reported ~40 seq/s figure;
# seq384 (the SQuAD convention) is FLOPs-scaled from it — per-sequence
# transformer FLOPs scale as S*(24*H^2 + 4*S*H), giving a 3.16x ratio
# between seq384 and seq128 for H=768, hence 40/3.16 = 12.7 seq/s.
V100_BERT_BASE_SEQ_PER_SEC = {128: 40.0, 384: 12.7}
BERT_METRIC = "bert_base_finetune_throughput"
BERT_UNIT = "sequences/sec/chip"


def _bert_script():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_bert.py")


def _resnet_line(result, batch, errors, degraded):
    line = {
        "metric": METRIC,
        "value": round(result["ips"], 2),
        "unit": UNIT,
        "vs_baseline": round(result["ips"] / V100_RESNET50_FP32_IMG_PER_SEC, 3),
        "batch": batch,
        "device": result["device"],
    }
    if result.get("hostfeed"):
        line["hostfeed"] = True
        line["plan_hit_rate"] = result.get("plan_hit_rate")
        line["h2d_overlapped"] = result.get("h2d_overlapped")
    for k in ("flops", "bytes_accessed", "out_bytes"):
        if result.get(k) is not None:
            line[k] = result[k]
            line["census_source"] = "live_census"
    if degraded:
        # a CPU number has no defensible relation to the V100 baseline
        line["vs_baseline"] = None
        line["degraded"] = "cpu fallback (TPU attempts failed: %s)" % (
            "; ".join(errors)[:400] or "none tried"
        )
    return line


def _bert_line(result, batch, seq_len, errors, degraded, flash=False):
    baseline = V100_BERT_BASE_SEQ_PER_SEC.get(seq_len)
    line = {
        "metric": BERT_METRIC,
        "value": round(result["sps"], 2),
        "unit": BERT_UNIT,
        # null for a seq len with no documented baseline constant
        "vs_baseline": round(result["sps"] / baseline, 3) if baseline else None,
        "batch": batch,
        "seq_len": seq_len,
        "device": result["device"],
    }
    if flash:
        line["flash_attention"] = True
    elif any(result.get(k) is not None
             for k in ("flops", "bytes_accessed", "out_bytes")):
        # dense path only: XLA cost analysis cannot see inside the flash
        # Pallas custom call, so a flash census would undercount — a
        # poisoned bytes baseline is worse than none (PERF.md round-5)
        for k in ("flops", "bytes_accessed", "out_bytes"):
            if result.get(k) is not None:
                line[k] = result[k]
        line["census_source"] = "live_census"
    if degraded:
        line["vs_baseline"] = None
        line["degraded"] = "cpu-fallback tiny-config (TPU attempts failed: %s)" % (
            "; ".join(errors)[:400] or "none tried"
        )
    return line


def _banked_resnet_line(errors):
    """Emit-line from the best banked ResNet TPU measurement, or None."""
    slot, e = bank_best("resnet50")
    if e is None:
        return None
    line = {
        "metric": METRIC,
        "value": e["value"],
        "unit": UNIT,
        "vs_baseline": round(e["value"] / V100_RESNET50_FP32_IMG_PER_SEC, 3),
        "batch": e.get("batch"),
        "device": "tpu",
        "banked": True,
        "git_sha": e.get("git_sha"),
        "measured_at": e.get("measured_at"),
    }
    if e.get("remat"):
        line["remat"] = True
    if e.get("hostfeed"):
        line["hostfeed"] = True
    if e.get("note"):
        line["provenance"] = e["note"]
    if errors:
        line["note"] = "banked TPU measurement; live attempts this run failed: %s" % (
            "; ".join(errors)[:300]
        )
    return line


def _banked_bert_line(errors):
    """Emit-line from the best banked BERT TPU measurement; prefers the
    defensible seq-384 config over the cheap seq-128 rung."""
    slot, e = bank_best("bert_seq384")
    seq = 384
    if e is None:
        slot, e = bank_best("bert_seq128")
        seq = 128
    if e is None:
        return None
    line = {
        "metric": BERT_METRIC,
        "value": e["value"],
        "unit": BERT_UNIT,
        "vs_baseline": round(e["value"] / V100_BERT_BASE_SEQ_PER_SEC[seq], 3),
        "batch": e.get("batch"),
        "seq_len": seq,
        "device": "tpu",
        "banked": True,
        "git_sha": e.get("git_sha"),
        "measured_at": e.get("measured_at"),
    }
    if slot.endswith("_flash"):
        line["flash_attention"] = True
    if e.get("note"):
        line["provenance"] = e["note"]
    if errors:
        line["note"] = "banked TPU measurement; live attempts this run failed: %s" % (
            "; ".join(errors)[:300]
        )
    return line


def _banked_gpt_line():
    """Emit-line from the best banked GPT-2 LM TPU measurement, or None
    (bonus family — bench_gpt.py owns the metric constants, including the
    derived V100-era GPT-2-small tokens/sec baseline documented in
    BASELINE.md; vs_baseline is non-null for the seq-1024 full config the
    constant was derived for)."""
    slot, e = bank_best("gpt_seq1024")
    if e is None:
        return None
    vs = None
    if e.get("seq_len") == 1024:
        try:
            import bench_gpt

            vs = round(e["value"] / bench_gpt.V100_GPT2_SMALL_TOK_PER_SEC, 3)
        except Exception:
            vs = None
    line = {
        "metric": e.get("metric", "gpt2_small_lm_throughput"),
        "value": e["value"],
        "unit": e.get("unit", "tokens/sec/chip"),
        "vs_baseline": vs,
        "batch": e.get("batch"),
        "seq_len": e.get("seq_len"),
        "device": "tpu",
        "banked": True,
        "git_sha": e.get("git_sha"),
        "measured_at": e.get("measured_at"),
    }
    if slot.endswith("_flash"):
        line["flash_attention"] = True
    if e.get("note"):
        line["provenance"] = e["note"]
    return line


def parent_main():
    total = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    hard_deadline = time.time() + total - 60.0
    base = _base_cfg()

    banked = {"resnet": None, "bert": None}  # best emitted-line per metric
    tpu_ok = {"resnet": False, "bert": False}
    # serving failures surface via note_fail's stderr trace only: the
    # rung is bank-only (no emit line exists to carry an error field)
    errors = {"resnet": [], "bert": [], "serving": [], "decode": []}
    tunnel_suspect = False
    # test hook: shrink TPU slots (hang-path tests shouldn't take 20 min)
    tpu_scale = float(os.environ.get("BENCH_TPU_SLOT_SCALE", "1"))
    # until the CPU fallbacks have run, TPU attempts may not eat into the
    # time reserved for them (170s + 150s) — a hanging tunnel must never
    # starve the fallback into the `budget < 30 -> skipped` guard
    cpu_reserve = [170.0 + 150.0]

    def tpu_deadline():
        return hard_deadline - cpu_reserve[0]

    def note_fail(metric, label, kind, err):
        errors[metric].append("%s: [%s] %s" % (label, kind, err))
        print(
            "bench[%s]: FAILED — [%s] %s" % (label, kind, err),
            file=sys.stderr,
            flush=True,
        )

    def try_resnet_tpu(batch, slot, steps=None, remat=None):
        nonlocal tunnel_suspect
        cfg = dict(base, batch=batch)
        if steps is not None:
            cfg["steps"] = steps
        if remat is not None:
            cfg["remat"] = remat
        label = "tpu-b%d%s%s" % (
            batch,
            "-remat" if cfg.get("remat") else "",
            "-hostfeed" if cfg.get("hostfeed") else "",
        )
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                line = _resnet_line(result, batch, [], False)
                if cfg.get("remat"):
                    line["remat"] = True
                bank_write(
                    "resnet50"
                    + ("_remat" if cfg.get("remat") else "")
                    + ("_hostfeed" if cfg.get("hostfeed") else ""),
                    _bank_entry(line),
                )
            prev = banked["resnet"]
            # bank-the-best: a slower later success (e.g. a bigger batch
            # that thrashes) never overwrites a faster banked TPU number
            if (
                prev is None
                or prev.get("degraded")
                or result["ips"] > prev["value"]
            ):
                banked["resnet"] = _resnet_line(result, batch, [], False)
                if cfg.get("remat"):
                    banked["resnet"]["remat"] = True
            tpu_ok["resnet"] = True
            tunnel_suspect = False
            return True
        note_fail("resnet", label, kind, err)
        if kind == "killed" and not probe_ok:
            tunnel_suspect = True
        if kind == "no_tpu":
            tunnel_suspect = True
        return False

    def try_bert_tpu(slot, batch=64, seq_len=128, flash=False):
        nonlocal tunnel_suspect
        cfg = dict(
            platform="",
            batch=batch,
            steps=10,
            warmup=2,
            full=True,
            seq_len=seq_len,
            flash=flash,
        )
        label = "bert-tpu-b%d-s%d%s" % (batch, seq_len, "-flash" if flash else "")
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline(), script=_bert_script()
        )
        if result is not None:
            if result["device"] == "tpu":
                bank_write(
                    "bert_seq%d%s" % (seq_len, "_flash" if flash else ""),
                    _bank_entry(_bert_line(result, batch, seq_len, [], False, flash)),
                )
            prev = banked["bert"]
            # a seq-384 number (the defensible SQuAD config) always beats
            # a banked seq-128 rung; within a seq len, bank-the-best
            if (
                prev is None
                or prev.get("degraded")
                or seq_len > prev.get("seq_len", 0)
                or (seq_len == prev.get("seq_len") and result["sps"] > prev["value"])
            ):
                banked["bert"] = _bert_line(result, batch, seq_len, [], False, flash)
            tpu_ok["bert"] = True
            tunnel_suspect = False
            return True
        note_fail("bert", label, kind, err)
        if kind in ("no_tpu",) or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_serving_tpu(slot):
        """BENCH_SERVING=1 rung: bank the dynamic-batching serving
        throughput on the GPT-2 export under 'gpt_serving'. Bank-only
        (never an emit line): requests/sec through the serving runtime is
        a different convention from the headline tokens/sec metrics."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": "",
            "serving": True,
            "batch": int(os.environ.get("BENCH_SERVING_BATCH", "8")),
            "seq_len": int(os.environ.get("BENCH_SERVING_SEQ", "128")),
            "layers": int(os.environ.get("BENCH_SERVING_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_SERVING_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_SERVING_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_SERVING_VOCAB", "50257")),
            "steps": int(os.environ.get("BENCH_SERVING_STEPS", "10")),
        }
        label = "serving-gpt-b%d-s%d" % (cfg["batch"], cfg["seq_len"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                # routed through _bank_entry so the banked fields can
                # never drift from its serving keep-list
                bank_write("gpt_serving", _bank_entry({
                    "metric": "gpt2_serving_throughput",
                    "value": round(result["rps"], 2),
                    "unit": "requests/sec/chip",
                    "batch": cfg["batch"],
                    "seq_len": cfg["seq_len"],
                    "device": "tpu",
                    "serving": True,
                    "offline_rps": round(result["offline_rps"], 2),
                    "p99_ms": result.get("p99_ms"),
                    "batch_fill": result.get("batch_fill"),
                    "bucket_hit_rate": result.get("bucket_hit_rate"),
                    "clients": result.get("clients"),
                }))
            return True
        note_fail("serving", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_decode_tpu(slot):
        """BENCH_DECODE=1 rung: bank autoregressive decode tokens/sec/user
        through the KV-cache continuous-batching engine under
        'gpt_decode'. Bank-only (never an emit line): a serving-side
        per-user rate, not a training-headline convention — bank_best
        guards it behind a 'decode'-containing prefix like the serving
        and hostfeed rungs."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": os.environ.get("BENCH_DECODE_PLATFORM", ""),
            "decode": True,
            "streams": int(os.environ.get("BENCH_DECODE_STREAMS", "8")),
            "max_len": int(os.environ.get("BENCH_DECODE_MAXLEN", "256")),
            "max_new": int(os.environ.get("BENCH_DECODE_MAXNEW", "64")),
            "prompt_len": int(os.environ.get("BENCH_DECODE_PROMPT", "32")),
            "layers": int(os.environ.get("BENCH_DECODE_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_DECODE_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_DECODE_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_DECODE_VOCAB", "50257")),
            "flash": os.environ.get("BENCH_DECODE_FLASH", "0") == "1",
        }
        label = "decode-gpt-%ds-m%d" % (cfg["streams"], cfg["max_len"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                bank_write("gpt_decode", _bank_entry(dict(result, **{
                    "metric": "gpt2_decode_throughput",
                    "value": round(result["tok_per_sec_user"], 2),
                    "unit": "tokens/sec/user",
                    "device": "tpu",
                    "decode": True,
                    "tok_per_sec": round(result["tok_per_sec"], 1),
                    "flash_attention": cfg["flash"],
                })))
            return True
        note_fail("decode", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_decode_prefix_tpu(slot):
        """BENCH_DECODE=1 prefix rung: tokens/sec/user AND mean hit TTFT
        through the prefix-cache + resume-prefill path at ~90% prefix
        share, banked under 'gpt_decode_prefix'. Bank-only, and doubly
        guarded: bank_best hides it from any prefix not containing
        'prefix' (an amortized shared-prefix rate must never replace the
        cold-prompt 'gpt_decode' headline)."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": os.environ.get("BENCH_DECODE_PLATFORM", ""),
            "decode": True,
            "prefix_cache": True,
            "streams": int(os.environ.get("BENCH_DECODE_STREAMS", "8")),
            "max_len": int(os.environ.get("BENCH_DECODE_MAXLEN", "256")),
            "max_new": int(os.environ.get("BENCH_DECODE_MAXNEW", "64")),
            "prompt_len": int(os.environ.get("BENCH_DECODE_PREFIX_PROMPT",
                                             "128")),
            "prefix_block": int(os.environ.get("BENCH_DECODE_PREFIX_BLOCK",
                                               "16")),
            "prefix_share": float(os.environ.get("BENCH_DECODE_PREFIX_SHARE",
                                                 "0.9")),
            "layers": int(os.environ.get("BENCH_DECODE_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_DECODE_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_DECODE_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_DECODE_VOCAB", "50257")),
            "flash": os.environ.get("BENCH_DECODE_FLASH", "0") == "1",
        }
        label = "decode-prefix-gpt-%ds-p%d" % (cfg["streams"],
                                               cfg["prompt_len"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                bank_write("gpt_decode_prefix", _bank_entry(dict(result, **{
                    "metric": "gpt2_decode_prefix_throughput",
                    "value": round(result["tok_per_sec_user"], 2),
                    "unit": "tokens/sec/user",
                    "device": "tpu",
                    "decode": True,
                    "prefix_cache": True,
                    "tok_per_sec": round(result["tok_per_sec"], 1),
                    "flash_attention": cfg["flash"],
                })))
            return True
        note_fail("decode", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_decode_paged_tpu(slot):
        """BENCH_DECODE=1 paged rung: tokens/sec/user through the
        block-table (paged KV) runtime at seq-4k max_len, with the pool
        byte-budget ANCHORED to the cold-prompt rung's geometry
        (streams x 256 contiguous rows) — the banked fact is that 16x
        longer streams fit at unchanged pool bytes because a slot holds
        ceil(len/block) blocks, not max_len rows. Banked under
        'gpt_decode_paged'; bank_best hides it from any prefix not
        containing 'paged'."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": os.environ.get("BENCH_DECODE_PLATFORM", ""),
            "decode": True,
            "streams": int(os.environ.get("BENCH_DECODE_STREAMS", "8")),
            "max_len": int(os.environ.get("BENCH_DECODE_PAGED_MAXLEN",
                                          "4096")),
            "max_new": int(os.environ.get("BENCH_DECODE_MAXNEW", "64")),
            "prompt_len": int(os.environ.get("BENCH_DECODE_PROMPT", "32")),
            "paged_block": int(os.environ.get("BENCH_DECODE_PAGED_BLOCK",
                                              "16")),
            "pool_anchor_len": int(os.environ.get("BENCH_DECODE_MAXLEN",
                                                  "256")),
            "layers": int(os.environ.get("BENCH_DECODE_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_DECODE_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_DECODE_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_DECODE_VOCAB", "50257")),
            "flash": os.environ.get("BENCH_DECODE_FLASH", "0") == "1",
        }
        label = "decode-paged-gpt-%ds-m%d" % (cfg["streams"],
                                              cfg["max_len"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                bank_write("gpt_decode_paged", _bank_entry(dict(result, **{
                    "metric": "gpt2_decode_paged_throughput",
                    "value": round(result["tok_per_sec_user"], 2),
                    "unit": "tokens/sec/user",
                    "device": "tpu",
                    "decode": True,
                    "tok_per_sec": round(result["tok_per_sec"], 1),
                    "flash_attention": cfg["flash"],
                })))
            return True
        note_fail("decode", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_decode_spec_tpu(slot):
        """BENCH_DECODE=1 speculative rung: tokens/sec/user with the
        k-token draft/verify armed, vs the width-1 baseline the SAME
        child measures first on identical paged geometry + workload.
        The drafter replays the baseline's recorded continuations at a
        controlled accuracy (default 0.9), so the banked speedup prices
        the fused verify + rollback machinery at that acceptance rather
        than n-gram drafter luck. Banked under 'gpt_decode_spec' with
        the 'spec' guard flag ('paged' is dropped from the entry — the
        spec guard alone isolates it; the rung is paged by
        construction)."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": os.environ.get("BENCH_DECODE_PLATFORM", ""),
            "decode": True,
            "streams": int(os.environ.get("BENCH_DECODE_STREAMS", "8")),
            "max_len": int(os.environ.get("BENCH_DECODE_MAXLEN", "256")),
            "max_new": int(os.environ.get("BENCH_DECODE_MAXNEW", "64")),
            "prompt_len": int(os.environ.get("BENCH_DECODE_PROMPT", "32")),
            "paged_block": int(os.environ.get("BENCH_DECODE_PAGED_BLOCK",
                                              "16")),
            "spec_tokens": int(os.environ.get("BENCH_DECODE_SPEC_TOKENS",
                                              "4")),
            "draft_accuracy": float(os.environ.get(
                "BENCH_DECODE_SPEC_ACCURACY", "0.9")),
            "layers": int(os.environ.get("BENCH_DECODE_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_DECODE_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_DECODE_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_DECODE_VOCAB", "50257")),
            "flash": os.environ.get("BENCH_DECODE_FLASH", "0") == "1",
        }
        label = "decode-spec-gpt-%ds-k%d" % (cfg["streams"],
                                             cfg["spec_tokens"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                entry = _bank_entry(dict(result, **{
                    "metric": "gpt2_decode_spec_throughput",
                    "value": round(result["tok_per_sec_user"], 2),
                    "unit": "tokens/sec/user",
                    "device": "tpu",
                    "decode": True,
                    "tok_per_sec": round(result["tok_per_sec"], 1),
                    "flash_attention": cfg["flash"],
                }))
                entry.pop("paged", None)
                bank_write("gpt_decode_spec", entry)
            return True
        note_fail("decode", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def try_decode_tp_tpu(slot):
        """BENCH_DECODE=1 tensor-parallel rung: tokens/sec/user with the
        paged engine's programs GSPMD-sharded over a {"model": TP} mesh
        (attention heads and KV pools partitioned, block tables
        replicated) — the serving shape the SPMD mainline exists for.
        Banked under 'gpt_decode_tp' with the 'tp' guard flag: a TP=2
        rate spends 2 devices per user, so bank_best hides it from every
        prefix not containing 'tp' (mirror of the paged/spec guards;
        'paged' is dropped from the entry — the rung is paged by
        construction and the tp guard alone isolates it)."""
        nonlocal tunnel_suspect
        cfg = {
            "platform": os.environ.get("BENCH_DECODE_PLATFORM", ""),
            "decode": True,
            "tp": int(os.environ.get("BENCH_DECODE_TP", "2")),
            "streams": int(os.environ.get("BENCH_DECODE_STREAMS", "8")),
            "max_len": int(os.environ.get("BENCH_DECODE_MAXLEN", "256")),
            "max_new": int(os.environ.get("BENCH_DECODE_MAXNEW", "64")),
            "prompt_len": int(os.environ.get("BENCH_DECODE_PROMPT", "32")),
            "paged_block": int(os.environ.get("BENCH_DECODE_PAGED_BLOCK",
                                              "16")),
            "layers": int(os.environ.get("BENCH_DECODE_LAYERS", "12")),
            "hidden": int(os.environ.get("BENCH_DECODE_HIDDEN", "768")),
            "heads": int(os.environ.get("BENCH_DECODE_HEADS", "12")),
            "vocab": int(os.environ.get("BENCH_DECODE_VOCAB", "50257")),
            "flash": os.environ.get("BENCH_DECODE_FLASH", "0") == "1",
        }
        label = "decode-tp-gpt-%ds-tp%d" % (cfg["streams"], cfg["tp"])
        result, kind, err, probe_ok = _run_attempt(
            label, cfg, slot * tpu_scale, tpu_deadline()
        )
        if result is not None:
            if result["device"] == "tpu":
                entry = _bank_entry(dict(result, **{
                    "metric": "gpt2_decode_tp_throughput",
                    "value": round(result["tok_per_sec_user"], 2),
                    "unit": "tokens/sec/user",
                    "device": "tpu",
                    "decode": True,
                    "tok_per_sec": round(result["tok_per_sec"], 1),
                    "flash_attention": cfg["flash"],
                }))
                entry.pop("paged", None)
                bank_write("gpt_decode_tp", entry)
            return True
        note_fail("decode", label, kind, err)
        if kind == "no_tpu" or (kind == "killed" and not probe_ok):
            tunnel_suspect = True
        return False

    def bank_cpu_fallbacks():
        # a banked TPU number makes the CPU fallback pointless — skip it
        # and leave the window to phase-D TPU retries
        if banked["resnet"] is None and bank_best("resnet50")[1] is None:
            cpu_cfg = dict(
                base,
                batch=int(os.environ.get("BENCH_CPU_BATCH", "8")),
                steps=min(base["steps"], 3),
                warmup=1,
                platform="cpu",
            )
            result, kind, err, _ = _run_attempt(
                "cpu-degraded", cpu_cfg, 170.0, hard_deadline
            )
            if result is not None:
                banked["resnet"] = _resnet_line(
                    result, cpu_cfg["batch"], errors["resnet"], True
                )
            else:
                note_fail("resnet", "cpu-degraded", kind, err)
        if banked["bert"] is None and bank_best("bert_seq")[1] is None:
            cfg = dict(
                platform="cpu", batch=4, steps=3, warmup=1, full=False, seq_len=128
            )
            result, kind, err, _ = _run_attempt(
                "bert-cpu-degraded", cfg, 150.0, hard_deadline, script=_bert_script()
            )
            if result is not None:
                banked["bert"] = _bert_line(result, 4, 128, errors["bert"], True)
            else:
                note_fail("bert", "bert-cpu-degraded", kind, err)

    # full-compile slot budget per batch — shared by phase A and the
    # phase-D retries so the two paths can never drift apart
    slot_for = {64: 260.0, 256: 240.0, 1024: 280.0}

    # ---- phase A: cheap-first TPU ladder — bank b64, then escalate ----
    if try_resnet_tpu(64, slot_for[64]):
        for b in (256, 1024):
            if not try_resnet_tpu(b, slot_for[b]):
                break
    # ---- phase B: BERT on TPU (skip if the tunnel looks dead) ----
    # cheap seq-128 rung first to bank *something*, then the defensible
    # SQuAD-convention seq-384 config (VERDICT r4 task 4)
    if not tunnel_suspect:
        if try_bert_tpu(260.0, batch=64, seq_len=128):
            try_bert_tpu(280.0, batch=24, seq_len=384)

    # ---- phase B2: opt-in serving rung (BENCH_SERVING=1; bank-only) ----
    if os.environ.get("BENCH_SERVING", "0") == "1" and not tunnel_suspect:
        try_serving_tpu(300.0)

    # ---- phase B3: opt-in decode rungs (BENCH_DECODE=1; bank-only):
    # the cold-prompt headline, then the ~90%-prefix-share rung ----
    if os.environ.get("BENCH_DECODE", "0") == "1" and not tunnel_suspect:
        try_decode_tpu(300.0)
        try_decode_prefix_tpu(300.0)
        # decode engine v2 rungs: the seq-4k block-table rate at the
        # cold rung's pool byte budget, then speculative vs width-1
        try_decode_paged_tpu(300.0)
        try_decode_spec_tpu(340.0)
        # SPMD mainline rung: the paged rate again, sharded over a
        # {"model": TP} mesh
        try_decode_tp_tpu(300.0)

    # ---- phase C: degraded CPU fallbacks for anything still missing ----
    bank_cpu_fallbacks()
    cpu_reserve[0] = 0.0  # fallbacks done: phase D may use the full window

    # ---- phase D: spend the remaining window on short TPU retries ----
    # (tunnel may come back mid-window; a banked CPU number is replaced
    # by any TPU success, and an existing TPU number is escalated)
    escalated = set()
    while time.time() < hard_deadline - 160.0:
        round_start = time.time()
        did_something = False
        if not tpu_ok["resnet"]:
            try_resnet_tpu(64, 150.0, steps=10)
            did_something = True
        elif not tpu_ok["bert"]:
            pass  # handled below
        else:
            b = banked["resnet"].get("batch", 0)
            nxt = 256 if b < 256 else 1024
            if b < 1024 and nxt not in escalated:
                escalated.add(nxt)
                try_resnet_tpu(nxt, slot_for[nxt])
                did_something = True
            elif "remat" not in escalated and not base["remat"]:
                # escalation done (or exhausted): probe the remat variant
                # at the banked batch — a DIFFERENT HLO, so budget a full
                # compile slot; bank-best keeps the faster of the two
                escalated.add("remat")
                try_resnet_tpu(b, slot_for.get(b, 280.0), remat=True)
                did_something = True
        if time.time() >= hard_deadline - 160.0:
            break
        if not tpu_ok["bert"]:
            try_bert_tpu(150.0)
            did_something = True
        elif banked["bert"] is not None and not banked["bert"].get("degraded"):
            # BERT banked: escalate seq 384, then the flash-attention rung
            # (VERDICT r4's own mitigation: probe flash only after a dense
            # number is banked, so a kernel failure can't zero the metric)
            if banked["bert"].get("seq_len") != 384 and "bert384" not in escalated:
                escalated.add("bert384")
                try_bert_tpu(280.0, batch=24, seq_len=384)
                did_something = True
            elif "bertflash" not in escalated:
                escalated.add("bertflash")
                try_bert_tpu(
                    280.0,
                    batch=banked["bert"].get("batch", 24),
                    seq_len=banked["bert"].get("seq_len", 384),
                    flash=True,
                )
                did_something = True
        if not did_something:
            break  # nothing left worth retrying — emit now
        # fast failures (e.g. instant no_tpu) must still SPREAD retries
        # across the window rather than hammering child spawns back-to-back
        spent = time.time() - round_start
        if spent < 120.0:
            time.sleep(min(120.0 - spent, max(0.0, hard_deadline - 160.0 - time.time())))

    # ---- emit: resnet (headline) first, bert second ----
    # preference per metric: live TPU line > banked TPU line (with
    # provenance) > degraded CPU line (vs_baseline null) > error line
    rc = 0
    line = banked["resnet"]
    if line is None or line.get("degraded"):
        line = _banked_resnet_line(errors["resnet"]) or line
    if line is not None:
        _emit(line)
    else:
        _emit(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": None,
                "error": "; ".join(errors["resnet"])[:800],
            }
        )
        rc = 1
    line = banked["bert"]
    if line is None or line.get("degraded"):
        line = _banked_bert_line(errors["bert"]) or line
    if line is not None:
        _emit(line)
    else:
        _emit(
            {
                "metric": BERT_METRIC,
                "value": 0.0,
                "unit": BERT_UNIT,
                "vs_baseline": None,
                "error": "; ".join(errors["bert"])[:800],
            }
        )
        rc = 1  # a zero-value metric line must not read as full success
    # bonus third family: GPT-2 LM line from the bank only (bench_gpt.py
    # and the watcher own the measurement; no bank entry -> no line, and
    # this can never flip rc — the headline contract is resnet + bert)
    gline = _banked_gpt_line()
    if gline is not None:
        _emit(gline)
    return rc


def main():
    try:
        return parent_main()
    except Exception:  # noqa: BLE001 - the driver contract is ONE JSON line, always
        import traceback

        traceback.print_exc()
        _emit(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": None,
                "error": "parent crash: %s"
                % traceback.format_exc().strip().splitlines()[-1][:300],
            }
        )
        return 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        _child_entry(json.loads(sys.argv[2]))
    else:
        sys.exit(main())
