"""Headline benchmark: ResNet-50 training throughput (images/sec) on one
chip (BASELINE.md metric 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the reference's V100+NCCL path. The
reference publishes no numbers in-repo (BASELINE.md), so the baseline
constant below is the commonly reported PaddlePaddle-era ResNet-50 fp32
V100 figure (~360 images/sec/GPU); the north-star target is >=0.9x.

Architecture (hardened for the axon TPU tunnel, which can HANG — not
raise — inside device discovery or compilation, where no in-process
watchdog can interrupt the C++ call):

- The parent process never imports jax. It spawns one child process per
  attempt with a HARD wall-clock timeout; on expiry the whole child
  process group is SIGKILLed.
- Attempt policy: start at batch 1024; a transient backend error (the
  tunnel's UNAVAILABLE) retries the SAME batch once; an OOM or hard
  timeout demotes to the next smaller batch (1024 -> 256 -> 64); a
  missing TPU skips straight to a clearly-labeled degraded CPU fallback
  so the driver always records a nonzero number when any backend works.
- The child emits "HB <phase> ..." heartbeat lines on stderr at every
  phase transition (probe / build / startup / warmup / step k/N); the
  parent relays them with elapsed timestamps, so a tail of the driver
  log shows exactly where a dead attempt died.
- The timeout slots are budgeted to fit the driver's 1500s watchdog
  with margin (420+380+320 TPU slots + a reserved 280s CPU slot,
  1400 < 1440), and the CPU fallback's slot is reserved up front so
  TPU failures can never starve it.
"""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V100_RESNET50_FP32_IMG_PER_SEC = 360.0
METRIC = "resnet50_train_throughput"
UNIT = "images/sec/chip"


# --------------------------------------------------------------------------
# child: one benchmark attempt (fixed config, no retries — parent owns those)
# --------------------------------------------------------------------------


def _hb(msg):
    print("HB %s" % msg, file=sys.stderr, flush=True)


def _child_fail(kind, msg):
    """Report a classified failure to the parent and exit nonzero."""
    print("CHILDERR " + json.dumps({"kind": kind, "msg": str(msg)[:300]}), flush=True)
    sys.exit(1)


def child_main(cfg):
    t_start = time.time()
    if cfg["platform"]:
        os.environ["JAX_PLATFORMS"] = cfg["platform"]

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # honor the explicit platform choice even when the axon
        # sitecustomize pinned jax_platforms via config (config beats env)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    _hb("probe start (device discovery + tiny compile)")
    if cfg["platform"] == "cpu":
        place = fluid.CPUPlace()
        device = "cpu"
    elif fluid.core.get_tpu_device_count() == 0:
        # fail fast rather than burn the hard timeout running a TPU-sized
        # batch on the CPU backend
        _child_fail("no_tpu", "no TPU device visible to this child")
    else:
        place = fluid.TPUPlace(0)
        device = "tpu"
    dev = fluid.core.get_jax_device(place)
    import jax.numpy as jnp

    x = jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
    y = jax.jit(lambda a: (a @ a).sum())(x)
    y.block_until_ready()
    _hb("probe ok %.1fs device=%s" % (time.time() - t_start, device))

    batch = cfg["batch"]
    steps = cfg["steps"]
    warmup = cfg["warmup"]
    depth = cfg["depth"]
    image_size = cfg["image_size"]

    t0 = time.time()
    _hb("build start (program construction)")
    main_prog, startup, feeds, loss, acc = resnet.build_resnet_train(
        depth=depth,
        class_num=1000,
        image_size=image_size,
        use_amp=cfg["amp"],
    )
    _hb("build ok %.1fs" % (time.time() - t0))

    t0 = time.time()
    _hb("startup start (param init compile+run)")
    exe = fluid.Executor(place)
    exe.run(startup)
    _hb("startup ok %.1fs" % (time.time() - t0))

    rs = np.random.RandomState(0)
    # pre-stage the batch on device: the benchmark measures training-step
    # compute (the reference's synthetic-data convention), not host link
    # bandwidth — on this rig H2D rides a network tunnel to the chip
    feed = {
        "img": jax.device_put(
            rs.rand(batch, 3, image_size, image_size).astype("float32"), dev
        ),
        "label": jax.device_put(rs.randint(0, 1000, (batch, 1)).astype("int64"), dev),
    }

    t0 = time.time()
    _hb("warmup start (%d steps, includes main-graph compile)" % warmup)
    for i in range(warmup):
        exe.run(main_prog, feed=feed, fetch_list=[loss])
        _hb("warmup step %d/%d done %.1fs" % (i + 1, warmup, time.time() - t0))
    # the executor cache key includes the fetch list, so the fetch-free
    # variant used by the timed loop must be compiled here, not inside it;
    # the follow-up fetching run DRAINS the async queue so none of that
    # work leaks into the timed window
    exe.run(main_prog, feed=feed, fetch_list=[])
    exe.run(main_prog, feed=feed, fetch_list=[loss])
    _hb("warmup fetch-free variant done %.1fs" % (time.time() - t0))

    _hb("timed run start (%d steps)" % steps)
    t0 = time.perf_counter()
    l = None
    for i in range(steps):
        # fetch the loss only on the final step: fetching synchronizes
        # host<->device every iteration, which on a tunneled chip serializes
        # the pipeline (VERDICT r2 weak #2)
        fetches = [loss] if i == steps - 1 else []
        out = exe.run(main_prog, feed=feed, fetch_list=fetches)
        if fetches:
            (l,) = out
    lval = float(np.asarray(l).ravel()[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(lval), "non-finite loss %r" % lval
    ips = batch * steps / dt
    _hb("timed run ok %.2fs loss=%.4f ips=%.1f" % (dt, lval, ips))

    print(
        "RESULT " + json.dumps({"ips": ips, "device": device, "loss": lval}),
        flush=True,
    )


def _child_entry(cfg):
    try:
        child_main(cfg)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 - classify for the parent
        s = str(e)
        if "RESOURCE_EXHAUSTED" in s or "Out of memory" in s or "out of memory" in s:
            kind = "oom"
        elif "UNAVAILABLE" in s or "Unavailable" in s or "DEADLINE_EXCEEDED" in s:
            kind = "transient"
        else:
            kind = "other"
        import traceback

        traceback.print_exc(file=sys.stderr)
        _child_fail(kind, s)


# --------------------------------------------------------------------------
# parent: attempt schedule, hard timeouts, heartbeat relay
# --------------------------------------------------------------------------


def _base_cfg():
    return {
        "steps": int(os.environ.get("BENCH_STEPS", "20")),
        "warmup": int(os.environ.get("BENCH_WARMUP", "3")),
        "depth": int(os.environ.get("BENCH_DEPTH", "50")),
        "image_size": int(os.environ.get("BENCH_IMG", "224")),
        "amp": os.environ.get("BENCH_AMP", "1") == "1",
        "platform": "",
    }


def _timeout_slots():
    """TPU timeout slots + reserved CPU-fallback slot. Overridable via
    BENCH_ATTEMPT_TIMEOUTS=t1,t2,...,tcpu (last value is the CPU slot)."""
    slots = [420.0, 380.0, 320.0]
    cpu_slot = 280.0
    if os.environ.get("BENCH_ATTEMPT_TIMEOUTS"):
        vals = [float(t) for t in os.environ["BENCH_ATTEMPT_TIMEOUTS"].split(",") if t]
        if len(vals) == 1:
            slots, cpu_slot = [vals[0]], vals[0]
        else:
            slots, cpu_slot = vals[:-1], vals[-1]
    return slots, cpu_slot


def _run_attempt(label, cfg, timeout, deadline, script=None):
    """Spawn one child attempt; kill its whole process group on timeout.
    Returns (result_dict_or_None, kind, error_str). kind in
    {"", "killed", "no_tpu", "oom", "transient", "other", "skipped"}.
    ``script`` lets sibling harnesses (bench_bert.py) reuse this exact
    streaming-relay + kill-timer machinery with their own --child entry."""
    budget = min(timeout, deadline - time.time())
    if budget < 30:
        return None, "skipped", "skipped: <30s left in budget"
    t0 = time.time()
    print(
        "bench[%s]: starting (hard timeout %.0fs)" % (label, budget),
        file=sys.stderr,
        flush=True,
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            script or os.path.abspath(__file__),
            "--child",
            json.dumps(cfg),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,  # own process group => killable even if wedged in C++
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    result, childerr, lines = None, None, []
    killed = False

    import threading

    def _kill():
        nonlocal killed
        killed = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass

    timer = threading.Timer(budget, _kill)
    timer.daemon = True
    timer.start()
    try:
        for line in proc.stdout:
            line = line.rstrip("\n")
            if line.startswith("RESULT "):
                try:
                    result = json.loads(line[len("RESULT ") :])
                except ValueError:
                    lines.append(line)
            elif line.startswith("CHILDERR "):
                try:
                    childerr = json.loads(line[len("CHILDERR ") :])
                except ValueError:
                    lines.append(line)
            else:
                lines.append(line)
                # relay heartbeats (and any backend noise) with timestamps
                print(
                    "bench[%s +%.0fs]: %s" % (label, time.time() - t0, line[:300]),
                    file=sys.stderr,
                    flush=True,
                )
        proc.wait()
    finally:
        timer.cancel()
    if result is not None:
        # a valid result beats a kill flag set in the exit race window
        return result, "", ""
    if childerr is not None:
        return None, childerr.get("kind", "other"), childerr.get("msg", "")
    if killed:
        last = lines[-1] if lines else "(no output)"
        return None, "killed", "killed at %.0fs hard timeout; last: %s" % (budget, last)
    last = next(
        (l for l in reversed(lines) if "Error" in l or "error" in l),
        lines[-1] if lines else "(no output)",
    )
    return None, "other", "exit rc=%d without result; last: %s" % (
        proc.returncode,
        last[:300],
    )


def _emit(out):
    print(json.dumps(out), flush=True)


def parent_main():
    total = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    hard_deadline = time.time() + total - 60.0
    base = _base_cfg()
    slots, cpu_slot = _timeout_slots()
    # reserve the CPU slot so TPU failures can never starve the fallback
    tpu_deadline = hard_deadline - cpu_slot

    first_batch = int(os.environ.get("BENCH_BATCH", "1024"))
    batches = [first_batch] + [b for b in (256, 64) if b < first_batch]
    errors = []
    bi = 0  # index into batches
    transient_retried = set()  # batches that already got their one retry
    slot_i = 0
    while bi < len(batches) and slot_i < len(slots):
        b = batches[bi]
        label = "tpu-b%d" % b
        result, kind, err = _run_attempt(
            label, dict(base, batch=b), slots[slot_i], tpu_deadline
        )
        slot_i += 1
        if result is not None:
            _emit(
                {
                    "metric": METRIC,
                    "value": round(result["ips"], 2),
                    "unit": UNIT,
                    "vs_baseline": round(
                        result["ips"] / V100_RESNET50_FP32_IMG_PER_SEC, 3
                    ),
                    "batch": b,
                    "device": result["device"],
                }
            )
            return 0
        errors.append("%s: [%s] %s" % (label, kind, err))
        print("bench[%s]: FAILED — [%s] %s" % (label, kind, err), file=sys.stderr, flush=True)
        if kind == "no_tpu":
            break  # straight to the CPU fallback
        if kind == "transient" and b not in transient_retried:
            transient_retried.add(b)  # retry the SAME batch once
            continue
        bi += 1  # oom / killed / other / repeat-transient: demote

    # degraded fallback: a clearly-labeled nonzero number beats a zero
    cpu_cfg = dict(
        base,
        batch=int(os.environ.get("BENCH_CPU_BATCH", "8")),
        steps=min(base["steps"], 3),
        warmup=1,
        platform="cpu",
    )
    result, kind, err = _run_attempt("cpu-degraded", cpu_cfg, cpu_slot, hard_deadline)
    if result is not None:
        _emit(
            {
                "metric": METRIC,
                "value": round(result["ips"], 2),
                "unit": UNIT,
                "vs_baseline": round(result["ips"] / V100_RESNET50_FP32_IMG_PER_SEC, 3),
                "batch": cpu_cfg["batch"],
                "device": "cpu",
                "degraded": "cpu fallback (TPU attempts failed: %s)"
                % ("; ".join(errors)[:400] or "none tried"),
            }
        )
        return 0
    errors.append("cpu-degraded: [%s] %s" % (kind, err))
    _emit(
        {
            "metric": METRIC,
            "value": 0.0,
            "unit": UNIT,
            "vs_baseline": 0.0,
            "error": "; ".join(errors)[:800],
        }
    )
    return 1


def main():
    try:
        return parent_main()
    except Exception:  # noqa: BLE001 - the driver contract is ONE JSON line, always
        import traceback

        traceback.print_exc()
        _emit(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": "parent crash: %s"
                % traceback.format_exc().strip().splitlines()[-1][:300],
            }
        )
        return 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        _child_entry(json.loads(sys.argv[2]))
    else:
        sys.exit(main())
